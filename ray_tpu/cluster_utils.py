"""Multi-node test harness: N real node processes on one host.

Role parity: reference ray.cluster_utils.Cluster
(reference: python/ray/cluster_utils.py:11, add_node :62, remove_node
:125) — the fixture every multi-node CI test uses. Each node is a real
``python -m ray_tpu._private.node`` subprocess (its own GCS connection,
raylet, shm store, worker pool), so failure injection = killing the
process, exactly like the reference's component-failure tests.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

import ray_tpu


class NodeHandle:
    def __init__(self, proc: subprocess.Popen, address_file: str,
                 head: bool):
        self.proc = proc
        self.address_file = address_file
        self.head = head
        self.gcs_address = ""
        self.raylet_address = ""
        self.session_dir = ""
        self.node_id: bytes = b""

    def wait_ready(self, timeout: float = 30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"node process exited rc={self.proc.returncode}")
            if os.path.exists(self.address_file):
                with open(self.address_file) as f:
                    lines = f.read().splitlines()
                if len(lines) >= 3:
                    self.gcs_address = lines[0]
                    self.raylet_address = lines[1]
                    self.session_dir = lines[2]
                    return self
            # raylint: disable=async-blocking — test-harness boot wait on the user thread; no loop exists yet
            time.sleep(0.05)
        raise TimeoutError("node did not come up")

    def kill(self):
        """Hard-kill (failure injection — reference: Cluster.remove_node
        with allow_graceful=False kills the raylet process)."""
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)

    def terminate(self):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5)


class Cluster:
    """Boot a head node + N worker nodes as subprocesses; drivers attach
    with ``ray_tpu.init(address=cluster.address)``."""

    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None,
                 connect: bool = False,
                 env: Optional[Dict[str, str]] = None):
        self.nodes: List[NodeHandle] = []
        self.head: Optional[NodeHandle] = None
        self._tmpdir = os.path.join(
            os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu"),
            f"cluster_{os.getpid()}_{int(time.time() * 1000)}")
        os.makedirs(self._tmpdir, exist_ok=True)
        self._env = dict(os.environ)
        self._env.setdefault("RAY_TPU_WORKER_JAX_PLATFORMS", "cpu")
        if env:
            self._env.update(env)
        self._counter = 0
        if initialize_head:
            self.head = self.add_node(head=True, **(head_node_args or {}))
        if connect:
            self.connect()

    @property
    def address(self) -> str:
        return self.head.gcs_address if self.head else ""

    def add_node(self, num_cpus: int = 1, head: bool = False,
                 resources: Optional[Dict[str, float]] = None,
                 node_name: str = "", wait: bool = True) -> NodeHandle:
        self._counter += 1
        address_file = os.path.join(self._tmpdir,
                                    f"node_{self._counter}.addr")
        cmd = [sys.executable, "-m", "ray_tpu._private.node",
               "--num-cpus", str(num_cpus),
               "--address-file", address_file]
        if node_name:
            cmd += ["--node-name", node_name]
        if resources:
            cmd += ["--resources",
                    ",".join(f"{k}={v}" for k, v in resources.items())]
        if head:
            cmd += ["--head"]
        else:
            assert self.head is not None, "head node required first"
            cmd += ["--gcs-address", self.head.gcs_address]
        proc = subprocess.Popen(
            cmd, env=self._env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        node = NodeHandle(proc, address_file, head)
        if wait:
            node.wait_ready()
            if not head:
                self._wait_node_count()
        self.nodes.append(node)
        return node

    def _alive_nodes(self) -> list:
        """Node info list from the GCS (drivers need not be connected)."""
        import asyncio

        from ray_tpu._private import rpc

        async def _q():
            conn = await rpc.connect(self.address, peer_name="cluster-util")
            try:
                reply, _ = await conn.call("GetAllNodeInfo", {})
                return [n for n in reply["nodes"] if n["alive"]]
            finally:
                await conn.close()

        loop = asyncio.new_event_loop()
        try:
            return loop.run_until_complete(_q())
        finally:
            loop.close()

    def _wait_node_count(self, timeout: float = 30.0):
        want = 1 + sum(1 for n in self.nodes if not n.head
                       and n.proc.poll() is None) + 1  # + the one joining
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self._alive_nodes()) >= want:
                return
            # raylint: disable=async-blocking — test-harness membership wait; subprocess polling has no event to wait on
            time.sleep(0.05)
        raise TimeoutError(f"cluster did not reach {want} nodes")

    def wait_for_nodes(self, count: int, timeout: float = 30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if len(self._alive_nodes()) == count:
                return
            # raylint: disable=async-blocking — test-harness membership wait; subprocess polling has no event to wait on
            time.sleep(0.05)
        raise TimeoutError(
            f"expected {count} alive nodes, have {len(self._alive_nodes())}")

    def remove_node(self, node: NodeHandle, allow_graceful: bool = False):
        if allow_graceful:
            node.terminate()
        else:
            node.kill()
        if node in self.nodes:
            self.nodes.remove(node)

    def connect(self, **kwargs):
        return ray_tpu.init(address=self.address, **kwargs)

    def shutdown(self):
        for node in reversed(self.nodes):
            node.terminate()
        self.nodes.clear()
        self.head = None
