"""Driver/worker global state and the public init/get/put/wait API.

Role parity: reference python/ray/worker.py — a process-wide ``Worker``
singleton holding the core worker, plus the module-level API surface
(`init`, `shutdown`, `get`, `put`, `wait`, `kill`, `cancel`,
`get_runtime_context`, `cluster_resources`, ...).
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu import exceptions as exc
from ray_tpu._private import protocol
from ray_tpu._private.config import RayTpuConfig, get_config, set_config
from ray_tpu._private.ids import ActorID, JobID, NodeID, TaskID, WorkerID
from ray_tpu._private.object_ref import ObjectRef

logger = logging.getLogger(__name__)


class Worker:
    """Process-global connection state."""

    def __init__(self):
        self.core = None            # CoreWorker
        self.node = None            # in-process head Node, if we started one
        self.mode: Optional[str] = None
        self.namespace: str = ""

    @property
    def connected(self) -> bool:
        return self.core is not None


global_worker = Worker()
_init_lock = threading.Lock()


def _tune_gc() -> None:
    """Make the cyclic GC proportional to garbage, not to heap size.

    The submit hot path allocates several container objects per task;
    with the default gen0 threshold (700) a full cluster heap gets
    re-scanned every ~100 submissions and the per-task cost doubles as
    the pending table grows. Freeze everything allocated up to init
    (module code, the connected core worker) out of the young
    generations and raise the thresholds — the same treatment the
    reference applies via its worker setup. Opt out with
    RAY_TPU_NO_GC_TUNING=1."""
    import gc

    global _saved_gc_threshold
    if os.environ.get("RAY_TPU_NO_GC_TUNING"):
        return
    gc.collect()
    gc.freeze()
    if _saved_gc_threshold is None:
        _saved_gc_threshold = gc.get_threshold()
    gc.set_threshold(10_000, 50, 50)


_saved_gc_threshold = None


def _untune_gc() -> None:
    """Undo _tune_gc at shutdown: the host application gets its GC
    policy back, and frozen objects return to the collectable heap so
    repeated init/shutdown cycles (test suites) don't accrete
    permanently uncollectable garbage."""
    import gc

    global _saved_gc_threshold
    if _saved_gc_threshold is not None:
        gc.set_threshold(*_saved_gc_threshold)
        _saved_gc_threshold = None
        gc.unfreeze()


def _require_connected() -> Worker:
    if not global_worker.connected:
        raise RuntimeError(
            "ray_tpu.init() must be called before using the API")
    return global_worker


def init(address: Optional[str] = None, *, num_cpus: Optional[int] = None,
         num_tpus: Optional[int] = None,
         resources: Optional[Dict[str, float]] = None,
         object_store_memory: Optional[int] = None,
         namespace: str = "", ignore_reinit_error: bool = False,
         runtime_env: Optional[Dict[str, Any]] = None,
         _system_config: Optional[Dict[str, Any]] = None,
         log_to_driver: bool = True) -> Dict[str, Any]:
    """Start (or connect to) a cluster and attach this process as a driver.

    Without ``address`` a head node (GCS + raylet + shm store) is started
    in-process and torn down at exit — reference: ray.init() auto-start
    (python/ray/worker.py init).
    """
    with _init_lock:
        if global_worker.connected:
            if ignore_reinit_error:
                return {"address": global_worker.core.gcs_address}
            raise RuntimeError("ray_tpu.init() called twice")

        from ray_tpu._private.core_worker import CoreWorker
        from ray_tpu._private.node import Node
        import ray_tpu.actor as actor_mod

        config = RayTpuConfig.create(_system_config)
        if object_store_memory:
            config.object_store_memory = object_store_memory
        set_config(config)

        if address is not None and address.startswith("ray://"):
            if runtime_env:
                # fail fast, matching the client-mode posture for
                # per-task runtime envs (util/client/client.py)
                raise ValueError(
                    "runtime_env is not supported in ray:// client mode")
            # Thin-client mode (reference: ray.init("ray://...") →
            # util/client). The whole API routes through a ClientCore
            # speaking to a cluster-side proxy.
            from ray_tpu.util.client import ClientCore

            client = ClientCore(address[len("ray://"):])
            global_worker.core = client
            global_worker.mode = "client"
            global_worker.namespace = namespace
            atexit.register(shutdown)
            return {"address": address, "mode": "client"}

        if address is None:
            node = Node(config=config,
                        num_cpus=num_cpus if num_cpus is not None
                        else max(1, os.cpu_count() or 1),
                        num_tpus=num_tpus,
                        custom_resources=resources)
            node.start_head()
            global_worker.node = node
            gcs_address = node.gcs_address
            raylet_address = node.raylet_address
            session_dir = node.session_dir
        else:
            gcs_address = address
            raylet_address, session_dir = _find_raylet(gcs_address, config)

        core = CoreWorker(mode="driver", config=config,
                          gcs_address=gcs_address,
                          raylet_address=raylet_address,
                          session_dir=session_dir,
                          log_to_driver=log_to_driver)
        core.connect()
        if runtime_env:
            core.set_job_runtime_env(runtime_env)
        _tune_gc()
        actor_mod.register_with_core_worker(core)
        global_worker.core = core
        global_worker.mode = "driver"
        global_worker.namespace = namespace
        atexit.register(shutdown)
        return {"address": gcs_address, "session_dir": session_dir,
                "job_id": core.job_id}


def _find_raylet(gcs_address: str, config: RayTpuConfig):
    """Connect via GCS and pick a raylet for this driver (prefer one on this
    host — all nodes in tests are local)."""
    import asyncio

    from ray_tpu._private import rpc

    async def _lookup():
        conn = await rpc.connect(gcs_address, peer_name="gcs-bootstrap")
        try:
            deadline = time.time() + config.rpc_connect_timeout_s
            while time.time() < deadline:
                reply, _ = await conn.call("GetAllNodeInfo", {})
                alive = [n for n in reply["nodes"] if n["alive"]]
                if alive:
                    return alive[0]["address"]
                await asyncio.sleep(0.1)
            raise RuntimeError("no alive nodes in cluster")
        finally:
            await conn.close()

    raylet_address = asyncio.run(_lookup())
    if raylet_address.startswith("unix://"):
        session_dir = os.path.dirname(os.path.dirname(
            raylet_address[len("unix://"):]))
    else:
        session_dir = os.path.join("/tmp/ray_tpu", "client-session")
    return raylet_address, session_dir


def shutdown():
    with _init_lock:
        w = global_worker
        if w.core is not None:
            try:
                w.core.shutdown()
            except Exception:
                pass
            w.core = None
        if w.node is not None:
            try:
                w.node.stop()
            except Exception:
                pass
            w.node = None
        w.mode = None
        _untune_gc()


def is_initialized() -> bool:
    return global_worker.connected


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    w = _require_connected()
    single = isinstance(refs, ObjectRef)
    try:
        ref_list = [refs] if single else list(refs)
    except TypeError:
        raise TypeError(
            f"get() expects an ObjectRef or a sequence of them, got "
            f"{type(refs).__name__}") from None
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
    values = w.core.get(ref_list, timeout=timeout)
    return values[0] if single else values


def put(value: Any) -> ObjectRef:
    w = _require_connected()
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return w.core.put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    w = _require_connected()
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return w.core.wait(refs, num_returns=num_returns, timeout=timeout,
                       fetch_local=fetch_local)


def put_sharded(array, mesh, spec):
    """Shard ``array`` (a numpy ndarray) over ``mesh`` according to
    ``spec`` (a PartitionSpec): one first-class object per shard, placed
    round-robin across the cluster's shm stores. Returns a
    ``DistributedArray`` handle whose shard refs free as one unit."""
    w = _require_connected()
    return w.core.put_sharded(array, mesh, spec)


def get_shard(darr, rank: int):
    """Fetch one shard of a DistributedArray by mesh rank."""
    w = _require_connected()
    return w.core.get_shard(darr, rank)


def assemble(darr):
    """Gather every shard and paste into one local ndarray."""
    w = _require_connected()
    return w.core.assemble(darr)


def reshard(darr, mesh, spec):
    """Re-partition a DistributedArray onto a new mesh/spec. Bulk bytes
    ride the striped data plane straight into the destination shards'
    segments (zero intermediate copies); falls back to get+put if a
    gather fails."""
    w = _require_connected()
    return w.core.reshard(darr, mesh, spec)


def all_gather(darr):
    """Collective: gather all shards into ONE replicated object and
    return its ObjectRef."""
    w = _require_connected()
    return w.core.all_gather(darr)


def all_reduce(darr, op: str = "sum"):
    """Collective: element-wise reduce full-shape partials (one per
    rank) into one object; reduction folds chunk-by-chunk on the
    destination raylet."""
    w = _require_connected()
    return w.core.all_reduce(darr, op=op)


def create_gang(world_size: int, *, resources=None, runtime_env=None):
    """Gang-schedule ``world_size`` workers across the cluster in ONE
    all-or-nothing lease round. Returns an ``SpmdGang`` whose ``run(fn)``
    launches one epoch-fenced SPMD step per member."""
    w = _require_connected()
    return w.core.create_gang(world_size, resources=resources,
                              runtime_env=runtime_env)


def kill(actor_handle, *, no_restart: bool = True):
    from ray_tpu.actor import ActorHandle
    w = _require_connected()
    if not isinstance(actor_handle, ActorHandle):
        raise TypeError("kill() expects an ActorHandle")
    w.core.kill_actor(actor_handle._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    # uniform across driver and ray:// client cores
    _require_connected().core.cancel(ref, force=force)


def cluster_resources() -> Dict[str, float]:
    w = _require_connected()
    reply, _ = w.core._run(w.core._gcs_call("GetClusterResources", {}))
    return reply["total"]


def available_resources() -> Dict[str, float]:
    w = _require_connected()
    reply, _ = w.core._run(w.core._gcs_call("GetClusterResources", {}))
    return reply["available"]


def experimental_internal_kv_put(key: bytes, value: bytes,
                                 overwrite: bool = True) -> bool:
    """Cluster-wide KV (reference: ray.experimental.internal_kv)."""
    w = _require_connected()
    reply, _ = w.core._run(w.core._gcs_call(
        "KVPut", protocol.KVPutRequest(
            key=key, overwrite=overwrite).to_header(), bufs=[value]))
    return reply["added"]


def experimental_internal_kv_get(key: bytes) -> Optional[bytes]:
    w = _require_connected()
    reply, bufs = w.core._run(w.core._gcs_call(
        "KVGet", protocol.KVGetRequest(key=key).to_header()))
    return bufs[0] if reply.get("found") else None


def experimental_internal_kv_del(key: bytes) -> bool:
    w = _require_connected()
    reply, _ = w.core._run(w.core._gcs_call(
        "KVDel", protocol.KVDelRequest(key=key).to_header()))
    return reply["deleted"]


def experimental_internal_kv_list(prefix: bytes = b"") -> List[bytes]:
    w = _require_connected()
    reply, _ = w.core._run(w.core._gcs_call(
        "KVKeys", protocol.KVKeysRequest(prefix=prefix).to_header()))
    return reply["keys"]


def nodes() -> List[dict]:
    w = _require_connected()
    reply, _ = w.core._run(w.core._gcs_call("GetAllNodeInfo", {}))
    out = []
    for n in reply["nodes"]:
        out.append({
            "NodeID": n["node_id"].hex(), "Alive": n["alive"],
            "NodeName": n["node_name"], "Address": n["address"],
            "Resources": n["resources_total"],
            # wire version agreed at RegisterNode (rolling-upgrade
            # visibility; absent key = pre-versioning GCS)
            "ProtocolVersion": n.get("negotiated_protocol_version", 1),
        })
    return out


class RuntimeContext:
    """Reference: python/ray/runtime_context.py."""

    def __init__(self, worker: Worker):
        self._worker = worker

    @property
    def job_id(self):
        return JobID(self._worker.core.job_id)

    @property
    def node_id(self):
        nid = self._worker.core.node_id
        return NodeID(nid) if nid else None

    @property
    def worker_id(self):
        return WorkerID(self._worker.core.worker_id)

    @property
    def current_actor_id(self):
        ex = self._worker.core.task_executor
        if ex is None or not ex._actor_id:
            return None
        return ActorID(ex._actor_id)

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get(self):
        return {"job_id": self.job_id, "node_id": self.node_id,
                "worker_id": self.worker_id}


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_require_connected())


def timeline() -> List[dict]:
    """Chrome-tracing events collected from all workers (reference:
    ray.timeline / state.chrome_tracing_dump)."""
    w = _require_connected()
    reply, _ = w.core._run(w.core._gcs_call("GetProfileEvents", {}))
    events = []
    for e in reply["events"]:
        events.append({
            "cat": e.get("event", "task"), "name": e.get("name", ""),
            "pid": e.get("worker_id", "")[:8], "tid": 0, "ph": "X",
            "ts": e.get("start", 0) * 1e6,
            "dur": (e.get("end", 0) - e.get("start", 0)) * 1e6,
        })
    return events


def memory_summary() -> str:
    """Cluster object-memory dump (the ``ray memory`` analog): this
    driver's ref table, the GCS object table's state/leak summary, and
    every node's store/recycle/map-cache/leak rollups. Delegates to
    ``ray_tpu.state.memory_summary()``."""
    from ray_tpu import state as state_mod

    return state_mod.memory_summary()
