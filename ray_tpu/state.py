"""Cluster introspection: the ``ray status`` / ``ray memory`` surface,
plus the task-lifecycle state API (``list_tasks`` / ``summary_tasks`` /
``timeline``).

Parity target: reference python/ray/state.py + the status/memory CLI
paths (reference: python/ray/scripts/scripts.py:1521 `ray status`,
:1497 `ray memory` dumping the ref table via GCS) and the state API
(reference: python/ray/util/state list_tasks over the GCS task table,
plus ``ray timeline``'s chrome-trace export, scripts.py `ray
timeline`). Task histories come from the GCS task-event table
(task_events.py): every task's ordered transition history — SUBMITTED
-> PENDING_LEASE -> DISPATCHED -> RUNNING -> FINISHED|FAILED with
retry/spillback annotations — with per-hop durations. Tasks dispatched
against a streaming-lease credit record CREDIT_DISPATCHED instead of
DISPATCHED and legitimately skip the PENDING_LEASE/LEASE_GRANTED hops
(the credit window replaced that round-trip).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu import worker as worker_mod


def _core():
    return worker_mod._require_connected().core


def node_stats() -> List[dict]:
    """Per-node resource + store/scheduler stats (raw)."""
    core = _core()
    reply = core.gcs_call_sync("GetNodeStatsSummary", {})
    return reply.get("nodes", [])


def summary_nodes() -> List[dict]:
    """Per-node summary rows built from the heartbeat-carried stats:
    resource totals, worker/store occupancy, and the memory-watchdog
    state — per-node ``workers_rss_bytes`` (sum of worker RSS at the
    last watchdog poll), the ``memory_pressure`` flag, and the honest
    cumulative ``memory_monitor_kills`` / ``lease_backpressure_rejects``
    counts (same counter style as the spill/eviction stats)."""
    out = []
    for n in node_stats():
        s = n.get("stats", {})
        nid = n["node_id"].hex() if isinstance(n["node_id"], bytes) \
            else str(n["node_id"])
        out.append({
            "node_id": nid,
            "node_name": n.get("node_name", ""),
            "alive": n.get("alive", False),
            "resources_total": n.get("resources_total", {}),
            "resources_available": n.get("resources_available", {}),
            "num_workers": s.get("num_workers", 0),
            "store_used_bytes": s.get("store_used_bytes", 0),
            "store_num_spills": s.get("store_num_spills", 0),
            "store_num_evictions": s.get("store_num_evictions", 0),
            "workers_rss_bytes": s.get("workers_rss_bytes", 0),
            "memory_pressure": s.get("memory_pressure", False),
            "memory_usage_fraction": s.get("memory_usage_fraction", 0.0),
            "memory_monitor_kills": s.get("memory_monitor_kills", 0),
            "lease_backpressure_rejects":
                s.get("lease_backpressure_rejects", 0),
        })
    return out


def metrics_address() -> str:
    """host:port of the cluster's Prometheus text endpoint."""
    addr = ray_tpu.experimental_internal_kv_get(
        b"__rtpu_metrics_address__")
    return addr.decode() if addr else ""


def status() -> str:
    """Human-readable cluster summary (the ``ray status`` analog)."""
    nodes = node_stats()
    alive = [n for n in nodes if n["alive"]]
    dead = [n for n in nodes if not n["alive"]]

    total: Dict[str, float] = {}
    avail: Dict[str, float] = {}
    for n in alive:
        for k, v in n["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
        for k, v in n["resources_available"].items():
            avail[k] = avail.get(k, 0.0) + v

    lines = ["======== Cluster status ========",
             f"Nodes: {len(alive)} alive" +
             (f", {len(dead)} dead" if dead else "")]
    lines.append("Resources:")
    for k in sorted(total):
        used = total[k] - avail.get(k, 0.0)
        lines.append(f"  {used:g}/{total[k]:g} {k} in use")
    pending = sum(n["stats"].get("num_pending_leases", 0) for n in alive)
    granted = sum(n["stats"].get("num_leases_granted", 0) for n in alive)
    spill = sum(n["stats"].get("num_spillbacks", 0) for n in alive)
    workers = sum(n["stats"].get("num_workers", 0) for n in alive)
    lines.append(f"Scheduler: {pending} pending leases, "
                 f"{granted} granted, {spill} spillbacks")
    lines.append(f"Workers: {workers}")
    store_bytes = sum(n["stats"].get("store_used_bytes", 0) for n in alive)
    store_objs = sum(n["stats"].get("store_num_objects", 0) for n in alive)
    lines.append(f"Object store: {store_objs} objects, "
                 f"{store_bytes / (1024 ** 2):.1f} MiB used")
    return "\n".join(lines)


def list_tasks(state: Optional[str] = None, name: Optional[str] = None,
               node: Optional[str] = None, job_id: Optional[str] = None,
               limit: int = 1000) -> List[dict]:
    """Per-task lifecycle records from the GCS task table.

    Each record carries the task's current ``state``, retry
    ``attempt`` count, and the full ordered transition history::

        {"task_id": hex, "job_id": hex, "name": str, "state": str,
         "attempt": int,
         "events": [{"state": str, "ts": float, "dur": float|None,
                     "attrs": {...}|None}, ...]}

    ``dur`` is the gap to the next transition (None on the last), so
    "where did this task spend its time" reads straight off the list.
    Filters: ``state`` exact (e.g. "RUNNING"), ``name`` substring,
    ``node`` node-id-hex prefix, ``job_id`` hex. The table is capped
    per job with counted eviction — ``summary_tasks()`` reports the
    truncation."""
    reply = _core().gcs_call_sync("GetTaskEvents", {
        "state": state, "name": name, "node": node, "job_id": job_id,
        "limit": limit})
    return reply.get("tasks", [])


def summary_tasks() -> dict:
    """Aggregate task counts by state and by (name, state), plus the
    honest loss accounting: per-job eviction counts and reporter-side
    ring-buffer drops."""
    reply = _core().gcs_call_sync("GetTaskSummary", {})
    return reply.get("summary", {})


def timeline(path: Optional[str] = None) -> List[dict]:
    """Chrome-trace export (chrome://tracing / Perfetto "trace event"
    JSON) merging THREE sources onto one wall clock:

    * task state intervals from the GCS task table (one "X" slice per
      transition, lasting until the next one),
    * tracing spans exported by util/tracing.py (RAY_TPU_TRACE=1),
    * data-plane pull/transfer intervals recorded by the raylets.

    So a single trace shows submit -> lease wait -> pull -> execute.
    Returns the event list; with ``path`` also writes it as JSON (load
    the file directly in chrome://tracing or ui.perfetto.dev)."""
    from ray_tpu.util import tracing

    reply = _core().gcs_call_sync("GetTaskEvents", {
        "limit": 100_000, "transfer_limit": 100_000})
    events: List[dict] = []
    pids: Dict[str, int] = {}

    def pid_of(label: str) -> int:
        p = pids.get(label)
        if p is None:
            p = pids[label] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": p,
                           "tid": 0, "ts": 0,
                           "args": {"name": label}})
        return p

    for tidx, task in enumerate(reply.get("tasks", []), start=1):
        pid = pid_of(f"tasks (job {task['job_id'] or '?'})")
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tidx, "ts": 0,
                       "args": {"name": f"{task['name']} "
                                        f"{task['task_id'][:8]}"}})
        for e in task["events"]:
            events.append({
                "name": e["state"], "cat": "task", "ph": "X",
                "ts": e["ts"] * 1e6,
                "dur": max(0.0, e["dur"] or 0.0) * 1e6,
                "pid": pid, "tid": tidx,
                "args": {"task_id": task["task_id"],
                         "attempt": task["attempt"],
                         **(e.get("attrs") or {})},
            })
    for tr in reply.get("transfers", []):
        pid = pid_of(f"data-plane {tr.get('node', '?')}")
        events.append({
            "name": f"pull {str(tr.get('object_id', ''))[:8]}",
            "cat": "data_plane", "ph": "X",
            "ts": tr.get("ts", 0.0) * 1e6,
            "dur": max(0.0, tr.get("dur", 0.0)) * 1e6,
            "pid": pid, "tid": 0, "args": dict(tr),
        })
    events.extend(tracing.to_chrome_trace(tracing.all_spans()))
    events.sort(key=lambda e: e.get("ts", 0))
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events


def memory_summary() -> str:
    """Ref-table + store dump (the ``ray memory`` analog).

    Covers this driver's ownership table (local refs, submitted-task
    refs, borrows, pinned bytes) and every node's store occupancy."""
    core = _core()
    rc = core.reference_counter
    lines = ["======== Object references (this driver) ========",
             f"{'OBJECT ID':<44} {'LOCAL':>5} {'SUBMITTED':>9} "
             f"{'BORROWERS':>9}  PLASMA"]
    n_shown = 0
    for oid, ref in list(rc._refs.items())[:200]:
        lines.append(
            f"{oid.hex():<44} {ref.local_refs:>5} "
            f"{ref.submitted_refs:>9} "
            f"{len(ref.borrowers or ()):>9}  "
            f"{'yes' if ref.in_plasma else 'no'}")
        n_shown += 1
    total = rc.num_tracked()
    if total > n_shown:
        lines.append(f"... and {total - n_shown} more")
    lines.append(f"Total tracked references: {total}")
    lines.append("")
    lines.append("======== Object store (per node) ========")
    for n in node_stats():
        s = n.get("stats", {})
        nid = n["node_id"].hex()[:12] if isinstance(n["node_id"], bytes) \
            else str(n["node_id"])[:12]
        lines.append(
            f"node {nid}: {s.get('store_num_objects', 0)} objects, "
            f"{s.get('store_used_bytes', 0) / (1024 ** 2):.1f} MiB, "
            f"{s.get('store_num_spills', 0)} spilled, "
            f"{s.get('store_num_evictions', 0)} evicted")
    return "\n".join(lines)
