"""Cluster introspection: the ``ray status`` / ``ray memory`` surface.

Parity target: reference python/ray/state.py + the status/memory CLI
paths (reference: python/ray/scripts/scripts.py:1521 `ray status`,
:1497 `ray memory` dumping the ref table via GCS).
"""

from __future__ import annotations

from typing import Dict, List

import ray_tpu
from ray_tpu import worker as worker_mod


def _core():
    return worker_mod._require_connected().core


def node_stats() -> List[dict]:
    """Per-node resource + store/scheduler stats (raw)."""
    core = _core()
    reply = core.gcs_call_sync("GetNodeStatsSummary", {})
    return reply.get("nodes", [])


def metrics_address() -> str:
    """host:port of the cluster's Prometheus text endpoint."""
    addr = ray_tpu.experimental_internal_kv_get(
        b"__rtpu_metrics_address__")
    return addr.decode() if addr else ""


def status() -> str:
    """Human-readable cluster summary (the ``ray status`` analog)."""
    nodes = node_stats()
    alive = [n for n in nodes if n["alive"]]
    dead = [n for n in nodes if not n["alive"]]

    total: Dict[str, float] = {}
    avail: Dict[str, float] = {}
    for n in alive:
        for k, v in n["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
        for k, v in n["resources_available"].items():
            avail[k] = avail.get(k, 0.0) + v

    lines = ["======== Cluster status ========",
             f"Nodes: {len(alive)} alive" +
             (f", {len(dead)} dead" if dead else "")]
    lines.append("Resources:")
    for k in sorted(total):
        used = total[k] - avail.get(k, 0.0)
        lines.append(f"  {used:g}/{total[k]:g} {k} in use")
    pending = sum(n["stats"].get("num_pending_leases", 0) for n in alive)
    granted = sum(n["stats"].get("num_leases_granted", 0) for n in alive)
    spill = sum(n["stats"].get("num_spillbacks", 0) for n in alive)
    workers = sum(n["stats"].get("num_workers", 0) for n in alive)
    lines.append(f"Scheduler: {pending} pending leases, "
                 f"{granted} granted, {spill} spillbacks")
    lines.append(f"Workers: {workers}")
    store_bytes = sum(n["stats"].get("store_used_bytes", 0) for n in alive)
    store_objs = sum(n["stats"].get("store_num_objects", 0) for n in alive)
    lines.append(f"Object store: {store_objs} objects, "
                 f"{store_bytes / (1024 ** 2):.1f} MiB used")
    return "\n".join(lines)


def memory_summary() -> str:
    """Ref-table + store dump (the ``ray memory`` analog).

    Covers this driver's ownership table (local refs, submitted-task
    refs, borrows, pinned bytes) and every node's store occupancy."""
    core = _core()
    rc = core.reference_counter
    lines = ["======== Object references (this driver) ========",
             f"{'OBJECT ID':<44} {'LOCAL':>5} {'SUBMITTED':>9} "
             f"{'BORROWERS':>9}  PLASMA"]
    n_shown = 0
    for oid, ref in list(rc._refs.items())[:200]:
        lines.append(
            f"{oid.hex():<44} {ref.local_refs:>5} "
            f"{ref.submitted_refs:>9} "
            f"{len(ref.borrowers or ()):>9}  "
            f"{'yes' if ref.in_plasma else 'no'}")
        n_shown += 1
    total = rc.num_tracked()
    if total > n_shown:
        lines.append(f"... and {total - n_shown} more")
    lines.append(f"Total tracked references: {total}")
    lines.append("")
    lines.append("======== Object store (per node) ========")
    for n in node_stats():
        s = n.get("stats", {})
        nid = n["node_id"].hex()[:12] if isinstance(n["node_id"], bytes) \
            else str(n["node_id"])[:12]
        lines.append(
            f"node {nid}: {s.get('store_num_objects', 0)} objects, "
            f"{s.get('store_used_bytes', 0) / (1024 ** 2):.1f} MiB, "
            f"{s.get('store_num_spills', 0)} spilled, "
            f"{s.get('store_num_evictions', 0)} evicted")
    return "\n".join(lines)
