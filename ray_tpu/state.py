"""Cluster introspection: the ``ray status`` / ``ray memory`` surface,
plus the task-lifecycle state API (``list_tasks`` / ``summary_tasks`` /
``timeline``).

Parity target: reference python/ray/state.py + the status/memory CLI
paths (reference: python/ray/scripts/scripts.py:1521 `ray status`,
:1497 `ray memory` dumping the ref table via GCS) and the state API
(reference: python/ray/util/state list_tasks over the GCS task table,
plus ``ray timeline``'s chrome-trace export, scripts.py `ray
timeline`). Task histories come from the GCS task-event table
(task_events.py): every task's ordered transition history — SUBMITTED
-> PENDING_LEASE -> DISPATCHED -> RUNNING -> FINISHED|FAILED with
retry/spillback annotations — with per-hop durations. Tasks dispatched
against a streaming-lease credit record CREDIT_DISPATCHED instead of
DISPATCHED and legitimately skip the PENDING_LEASE/LEASE_GRANTED hops
(the credit window replaced that round-trip).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu import worker as worker_mod
from ray_tpu._private import protocol


def _core():
    return worker_mod._require_connected().core


def node_stats() -> List[dict]:
    """Per-node resource + store/scheduler stats (raw)."""
    core = _core()
    reply = core.gcs_call_sync("GetNodeStatsSummary", {})
    return reply.get("nodes", [])


def summary_nodes() -> List[dict]:
    """Per-node summary rows built from the heartbeat-carried stats:
    resource totals, worker/store occupancy, and the memory-watchdog
    state — per-node ``workers_rss_bytes`` (sum of worker RSS at the
    last watchdog poll), the ``memory_pressure`` flag, and the honest
    cumulative ``memory_monitor_kills`` / ``lease_backpressure_rejects``
    counts (same counter style as the spill/eviction stats)."""
    out = []
    for n in node_stats():
        s = n.get("stats", {})
        nid = n["node_id"].hex() if isinstance(n["node_id"], bytes) \
            else str(n["node_id"])
        out.append({
            "node_id": nid,
            "node_name": n.get("node_name", ""),
            "alive": n.get("alive", False),
            "resources_total": n.get("resources_total", {}),
            "resources_available": n.get("resources_available", {}),
            "num_workers": s.get("num_workers", 0),
            "store_used_bytes": s.get("store_used_bytes", 0),
            "store_num_spills": s.get("store_num_spills", 0),
            "store_num_evictions": s.get("store_num_evictions", 0),
            "workers_rss_bytes": s.get("workers_rss_bytes", 0),
            "memory_pressure": s.get("memory_pressure", False),
            "memory_usage_fraction": s.get("memory_usage_fraction", 0.0),
            "memory_monitor_kills": s.get("memory_monitor_kills", 0),
            "lease_backpressure_rejects":
                s.get("lease_backpressure_rejects", 0),
            # object-plane rollups (heartbeat-carried, ISSUE 13): the
            # memory truth GetNodeStats always computed, now dashboard-
            # visible without a per-node RPC
            "store_capacity_bytes": s.get("store_capacity_bytes", 0),
            "store_num_pinned": s.get("store_num_pinned", 0),
            "store_recycle_bytes": s.get("store_recycle_bytes", 0),
            "store_recycle_segments": s.get("store_recycle_segments", 0),
            "store_lent_segments": s.get("store_lent_segments", 0),
            "store_lent_bytes": s.get("store_lent_bytes", 0),
            "map_cache_bytes": s.get("map_cache_bytes", 0),
            "map_cache_entries": s.get("map_cache_entries", 0),
            "data_plane_inflight_bytes":
                s.get("data_plane_inflight_bytes", 0),
            "objects_leaked": s.get("objects_leaked", 0),
            "leak_reclaims": s.get("leak_reclaims", 0),
            # control-plane rollups (heartbeat-carried, ISSUE 14): the
            # instrumented-event-loop truth per node — scheduling
            # delay of a ready callback on the raylet loop, and how
            # many handlers/callbacks crossed the slow threshold
            "loop_lag_p50_ms": s.get("loop_lag_p50_ms", 0.0),
            "loop_lag_p99_ms": s.get("loop_lag_p99_ms", 0.0),
            "loop_lag_max_ms": s.get("loop_lag_max_ms", 0.0),
            "loop_slow_callbacks": s.get("loop_slow_callbacks", 0),
        })
    return out


def metrics_address() -> str:
    """host:port of the cluster's Prometheus text endpoint."""
    addr = ray_tpu.experimental_internal_kv_get(
        b"__rtpu_metrics_address__")
    return addr.decode() if addr else ""


def status() -> str:
    """Human-readable cluster summary (the ``ray status`` analog)."""
    nodes = node_stats()
    alive = [n for n in nodes if n["alive"]]
    dead = [n for n in nodes if not n["alive"]]

    total: Dict[str, float] = {}
    avail: Dict[str, float] = {}
    for n in alive:
        for k, v in n["resources_total"].items():
            total[k] = total.get(k, 0.0) + v
        for k, v in n["resources_available"].items():
            avail[k] = avail.get(k, 0.0) + v

    lines = ["======== Cluster status ========",
             f"Nodes: {len(alive)} alive" +
             (f", {len(dead)} dead" if dead else "")]
    lines.append("Resources:")
    for k in sorted(total):
        used = total[k] - avail.get(k, 0.0)
        lines.append(f"  {used:g}/{total[k]:g} {k} in use")
    pending = sum(n["stats"].get("num_pending_leases", 0) for n in alive)
    granted = sum(n["stats"].get("num_leases_granted", 0) for n in alive)
    spill = sum(n["stats"].get("num_spillbacks", 0) for n in alive)
    workers = sum(n["stats"].get("num_workers", 0) for n in alive)
    lines.append(f"Scheduler: {pending} pending leases, "
                 f"{granted} granted, {spill} spillbacks")
    lines.append(f"Workers: {workers}")
    store_bytes = sum(n["stats"].get("store_used_bytes", 0) for n in alive)
    store_objs = sum(n["stats"].get("store_num_objects", 0) for n in alive)
    lines.append(f"Object store: {store_objs} objects, "
                 f"{store_bytes / (1024 ** 2):.1f} MiB used")
    return "\n".join(lines)


def list_tasks(state: Optional[str] = None, name: Optional[str] = None,
               node: Optional[str] = None, job_id: Optional[str] = None,
               limit: int = 1000) -> List[dict]:
    """Per-task lifecycle records from the GCS task table.

    Each record carries the task's current ``state``, retry
    ``attempt`` count, and the full ordered transition history::

        {"task_id": hex, "job_id": hex, "name": str, "state": str,
         "attempt": int,
         "events": [{"state": str, "ts": float, "dur": float|None,
                     "attrs": {...}|None}, ...]}

    ``dur`` is the gap to the next transition (None on the last), so
    "where did this task spend its time" reads straight off the list.
    Filters: ``state`` exact (e.g. "RUNNING"), ``name`` substring,
    ``node`` node-id-hex prefix, ``job_id`` hex. The table is capped
    per job with counted eviction — ``summary_tasks()`` reports the
    truncation."""
    reply = _core().gcs_call_sync("GetTaskEvents", {
        "state": state, "name": name, "node": node, "job_id": job_id,
        "limit": limit})
    return reply.get("tasks", [])


def summary_tasks() -> dict:
    """Aggregate task counts by state and by (name, state), plus the
    honest loss accounting: per-job eviction counts and reporter-side
    ring-buffer drops."""
    reply = _core().gcs_call_sync("GetTaskSummary", {})
    return reply.get("summary", {})


def list_objects(state: Optional[str] = None, owner: Optional[str] = None,
                 node: Optional[str] = None, job_id: Optional[str] = None,
                 leaked: Optional[bool] = None,
                 limit: int = 1000) -> List[dict]:
    """Per-object lifecycle records from the GCS object table, merged
    with this driver's live reference counts.

    Each record carries the object's ``owner``, ``size``, current
    ``state``, the ``leaked`` verdict, and the full ordered transition
    history (CREATED -> SEALED/PINNED -> BORROWED/PULLED/locations ->
    OUT_OF_SCOPE/FREED, object_events.py)::

        {"object_id": hex, "job_id": hex, "owner": str, "size": int,
         "state": str, "leaked": bool,
         "events": [{"state", "ts", "dur", "attrs"}, ...],
         # for objects this driver still tracks:
         "ref_counts": {"local", "submitted", "borrowers", "contains",
                        "lineage_pinned"}, "locations": [hex12, ...]}

    Filters: ``state`` exact, ``owner`` substring, ``node``
    node-id-hex prefix, ``job_id`` hex, ``leaked`` exact. The table is
    capped per job with counted eviction — ``summary_objects()``
    reports the truncation. Small in-process values that never touched
    plasma/borrowing emit no events by design and do NOT appear here;
    ``memory_summary()`` dumps the live driver ref table that covers
    them."""
    core = _core()
    reply = core.gcs_call_sync("GetObjectEvents", {
        "state": state, "owner": owner, "node": node, "job_id": job_id,
        "leaked": leaked, "limit": limit})
    records = reply.get("objects", [])
    rc = core.reference_counter
    with rc._lock:  # noqa: SLF001 — read-only snapshot under the lock
        live = dict(rc._refs)
    for rec in records:
        ref = live.get(bytes.fromhex(rec["object_id"]))
        if ref is None:
            continue
        rec["ref_counts"] = {
            "local": ref.local_refs,
            "submitted": ref.submitted_refs,
            "borrowers": len(ref.borrowers or ()),
            "contains": len(ref.contains or ()),
            "lineage_pinned": ref.pinned_lineage,
        }
        rec["locations"] = [n.hex()[:12]
                            for n in sorted(ref.locations or ())]
    return records


def list_rpc(method: Optional[str] = None,
             reporter: Optional[str] = None,
             side: Optional[str] = None) -> List[dict]:
    """Per-method RPC telemetry rows from the GCS flight-recorder table
    (rpc.py RpcTelemetry; the instrumented-io-context analog).

    One row per (reporter, side, method)::

        {"reporter": "node-ab12…|driver-…|worker-…|gcs",
         "side": "server"|"client", "method": str,
         "count", "errors", "timeouts", "inflight",
         "bytes_in", "bytes_out", "mean_ms", "queue_mean_ms",
         "max_ms",                     # WINDOWED max (recent behavior)
         "exec":  {"count","p50_ms","p90_ms","p99_ms","max_ms"},
         "queue": {"count","p50_ms","p90_ms","p99_ms","max_ms"},
         "dropped_samples": int}       # honest reservoir truncation

    ``queue`` is frame-arrival -> handler-start (loop scheduling
    delay), ``exec`` is handler run time — reported apart so "the loop
    was busy" and "the handler was slow" are distinguishable. Client
    rows carry call latency under ``exec`` plus ``timeouts`` and push
    counts/bytes. Filters: ``method`` substring, ``reporter`` prefix,
    ``side`` exact. Raylets ship on the heartbeat, workers/drivers on
    the metrics cadence; reporters age out after 60 s of silence."""
    reply = _core().gcs_call_sync(
        "GetRpcTelemetry",
        protocol.GetRpcTelemetryRequest(
            method=method, reporter=reporter, side=side).to_header())
    return reply.get("rows", [])


def summary_rpc() -> dict:
    """Cluster-wide per-method aggregate of the RPC telemetry,
    computed GCS-side (rpc.py RpcTelemetryTable.summary — the same
    block /api/rpc serves): counts/bytes/errors/in-flight from the
    SERVER rows (one observation per call — client rows of the same
    method would double-count it; client-only methods such as one-way
    pushes fall back to their client rows), ``timeouts`` from the
    client rows, percentiles from the WORST reporter of either side
    (a "slowest node" view, since raw reservoirs never leave their
    process) — plus per-reporter event-loop lag blocks and the bounded
    slow-call ring's size."""
    reply = _core().gcs_call_sync(
        "GetRpcTelemetry",
        protocol.GetRpcTelemetryRequest().to_header())
    return {
        "methods": reply.get("summary", {}),
        "loops": reply.get("loops", {}),
        "slow_calls": len(reply.get("slow_calls", [])),
        "slow_calls_dropped": reply.get("slow_calls_dropped", 0),
    }


def list_cluster_events(severity: Optional[str] = None,
                        label: Optional[str] = None,
                        source: Optional[str] = None,
                        node: Optional[str] = None,
                        limit: int = 1000) -> List[dict]:
    """Structured cluster events from the GCS ClusterEventTable
    (events.py): node death, GCS restarts, worker/OOM kills, leak
    reclaims, credit revokes, backpressure engage/clear, zygote
    fallbacks — each with a GCS-assigned monotonic ``seq`` so ordering
    is total even across reporter clock skew::

        {"seq": int, "timestamp": float, "severity": str,
         "label": str, "message": str, "source_type": str,
         "pid": int, "custom_fields": {...}}

    Filters: ``severity`` exact, ``label`` substring, ``source`` exact,
    ``node`` node-id-hex prefix. The table is capped with counted
    eviction; ``summary_cluster_events()`` reports the truncation."""
    reply = _core().gcs_call_sync(
        "GetClusterEvents",
        protocol.GetClusterEventsRequest(
            severity=severity, label=label, source=source, node=node,
            limit=limit).to_header())
    return reply.get("events", [])


def summary_cluster_events() -> dict:
    """Event counts by severity/label plus the honest truncation
    counters (table evictions, reporter-side buffer drops)."""
    reply = _core().gcs_call_sync(
        "GetClusterEvents",
        protocol.GetClusterEventsRequest(limit=1).to_header())
    return reply.get("summary", {})


def summary_objects() -> dict:
    """Aggregate object counts by state plus the honest loss
    accounting (per-job eviction counts, reporter drops) and the
    leak-detector verdict: ``leaked`` counts store-held objects whose
    owner holds no reference RIGHT NOW — the chaos schedules assert it
    returns to 0 after every soak."""
    reply = _core().gcs_call_sync(
        "GetObjectSummary", protocol.GetObjectSummaryRequest().to_header())
    return reply.get("summary", {})


def timeline(path: Optional[str] = None) -> List[dict]:
    """Chrome-trace export (chrome://tracing / Perfetto "trace event"
    JSON) merging FIVE sources onto one wall clock:

    * task state intervals from the GCS task table (one "X" slice per
      transition, lasting until the next one),
    * object lifecycle intervals from the GCS object table (cat
      "object": allocation/seal, pin/borrow/pull, free — same clock as
      the tasks that produced and consumed them),
    * tracing spans exported by util/tracing.py (RAY_TPU_TRACE=1),
    * data-plane pull/transfer intervals recorded by the raylets,
    * SLOW RPC calls (cat "rpc"): every server handler or client call
      that exceeded ``loop_slow_callback_threshold_ms``, attributed by
      method name with its queueing vs exec split — bounded records
      from the control-plane flight recorder (rpc.py), so a straggler
      trace shows whether the CONTROL PLANE (not the task) was the
      slow part.

    So a single trace shows submit -> lease wait -> pull -> execute
    with the objects' lifetimes underneath. Returns the event list;
    with ``path`` also writes it as JSON (load the file directly in
    chrome://tracing or ui.perfetto.dev)."""
    from ray_tpu.util import tracing

    reply = _core().gcs_call_sync("GetTaskEvents", {
        "limit": 100_000, "transfer_limit": 100_000})
    events: List[dict] = []
    pids: Dict[str, int] = {}

    def pid_of(label: str) -> int:
        p = pids.get(label)
        if p is None:
            p = pids[label] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": p,
                           "tid": 0, "ts": 0,
                           "args": {"name": label}})
        return p

    for tidx, task in enumerate(reply.get("tasks", []), start=1):
        pid = pid_of(f"tasks (job {task['job_id'] or '?'})")
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tidx, "ts": 0,
                       "args": {"name": f"{task['name']} "
                                        f"{task['task_id'][:8]}"}})
        for e in task["events"]:
            events.append({
                "name": e["state"], "cat": "task", "ph": "X",
                "ts": e["ts"] * 1e6,
                "dur": max(0.0, e["dur"] or 0.0) * 1e6,
                "pid": pid, "tid": tidx,
                "args": {"task_id": task["task_id"],
                         "attempt": task["attempt"],
                         **(e.get("attrs") or {})},
            })
    obj_reply = _core().gcs_call_sync("GetObjectEvents",
                                      {"limit": 100_000})
    for oidx, obj in enumerate(obj_reply.get("objects", []), start=1):
        pid = pid_of("objects")
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": oidx, "ts": 0,
                       "args": {"name": obj["object_id"][:8]}})
        for e in obj["events"]:
            events.append({
                "name": e["state"], "cat": "object", "ph": "X",
                "ts": e["ts"] * 1e6,
                "dur": max(0.0, e["dur"] or 0.0) * 1e6,
                "pid": pid, "tid": oidx,
                "args": {"object_id": obj["object_id"],
                         "owner": obj["owner"], "size": obj["size"],
                         **(e.get("attrs") or {})},
            })
    for tr in reply.get("transfers", []):
        pid = pid_of(f"data-plane {tr.get('node', '?')}")
        events.append({
            "name": f"pull {str(tr.get('object_id', ''))[:8]}",
            "cat": "data_plane", "ph": "X",
            "ts": tr.get("ts", 0.0) * 1e6,
            "dur": max(0.0, tr.get("dur", 0.0)) * 1e6,
            "pid": pid, "tid": 0, "args": dict(tr),
        })
    rpc_reply = _core().gcs_call_sync(
        "GetRpcTelemetry", protocol.GetRpcTelemetryRequest().to_header())
    for sc in rpc_reply.get("slow_calls", []):
        pid = pid_of(f"rpc ({sc.get('reporter', '?')})")
        events.append({
            "name": f"{sc.get('side', '?')} {sc.get('method', '?')}",
            "cat": "rpc", "ph": "X",
            "ts": sc.get("ts", 0.0) * 1e6,
            "dur": max(0.0, sc.get("dur_ms", 0.0)) * 1e3,
            "pid": pid, "tid": 0, "args": dict(sc),
        })
    events.extend(tracing.to_chrome_trace(tracing.all_spans()))
    events.sort(key=lambda e: e.get("ts", 0))
    if path:
        with open(path, "w") as f:
            json.dump(events, f)
    return events


def memory_summary() -> str:
    """Cluster object-memory dump (the ``ray memory`` analog).

    Three sections: this driver's live ownership table (local refs,
    submitted-task refs, borrows, plasma residency), the cluster-wide
    object table's state/leak summary (object_events.py — honest
    truncation counters included), and the per-node store rollups the
    heartbeat carries: occupancy, recycle pool, lent (AllocSegment)
    leases, writer map cache, data-plane in-flight bytes, and the
    leak-detector verdicts."""
    core = _core()
    rc = core.reference_counter
    lines = ["======== Object references (this driver) ========",
             f"{'OBJECT ID':<44} {'LOCAL':>5} {'SUBMITTED':>9} "
             f"{'BORROWERS':>9}  PLASMA"]
    n_shown = 0
    for oid, ref in list(rc._refs.items())[:200]:
        lines.append(
            f"{oid.hex():<44} {ref.local_refs:>5} "
            f"{ref.submitted_refs:>9} "
            f"{len(ref.borrowers or ()):>9}  "
            f"{'yes' if ref.in_plasma else 'no'}")
        n_shown += 1
    total = rc.num_tracked()
    if total > n_shown:
        lines.append(f"... and {total - n_shown} more")
    lines.append(f"Total tracked references: {total}")
    lines.append("")
    lines.append("======== Object table (cluster) ========")
    try:
        s = summary_objects()
    except Exception:  # noqa: BLE001 — summary must degrade, not raise
        s = {}
    by_state = s.get("by_state", {})
    lines.append(
        f"{s.get('num_objects', 0)} objects tracked, "
        f"{s.get('total_size_bytes', 0) / (1024 ** 2):.1f} MiB, "
        f"leaked: {s.get('leaked', 0)}")
    if by_state:
        lines.append("  by state: " + ", ".join(
            f"{k}={v}" for k, v in sorted(by_state.items())))
    dropped = s.get("dropped_events", 0)
    evicted = sum(s.get("evicted_objects", {}).values())
    if dropped or evicted:
        lines.append(f"  truncation: {evicted} records evicted, "
                     f"{dropped} events dropped (honest counters)")
    lines.append("")
    lines.append("======== Object store (per node) ========")
    for n in node_stats():
        s = n.get("stats", {})
        nid = n["node_id"].hex()[:12] if isinstance(n["node_id"], bytes) \
            else str(n["node_id"])[:12]
        mib = 1024 ** 2
        lines.append(
            f"node {nid}: {s.get('store_num_objects', 0)} objects "
            f"({s.get('store_num_pinned', 0)} pinned), "
            f"{s.get('store_used_bytes', 0) / mib:.1f}/"
            f"{s.get('store_capacity_bytes', 0) / mib:.0f} MiB, "
            f"{s.get('store_num_spills', 0)} spilled, "
            f"{s.get('store_num_evictions', 0)} evicted")
        lines.append(
            f"  recycle pool {s.get('store_recycle_bytes', 0) / mib:.1f}"
            f" MiB/{s.get('store_recycle_segments', 0)} segs, "
            f"{s.get('store_lent_segments', 0)} lent, map cache "
            f"{s.get('map_cache_bytes', 0) / mib:.1f} MiB/"
            f"{s.get('map_cache_entries', 0)} entries, pull in-flight "
            f"{s.get('data_plane_inflight_bytes', 0) / mib:.1f} MiB, "
            f"leaked {s.get('objects_leaked', 0)} "
            f"(reclaimed {s.get('leak_reclaims', 0)})")
    return "\n".join(lines)
