import time, os
import ray_tpu

ray_tpu.init(num_cpus=2)

@ray_tpu.remote
def f():
    return b"ok"

ray_tpu.get(f.remote())
core = ray_tpu.worker.global_worker.core
tmpl = f._template[2]
ctx = core._fast_ctx
prefix = core._task_lineage_prefix

# freeze the io-loop drain: flag stays True so submit never wakes it
core._submit_scheduled = True

N = 300_000
t0 = time.perf_counter()
for _ in range(N):
    ctx.submit(tmpl, prefix, None)
dt = time.perf_counter() - t0
print(f"ctx.submit isolated: {dt/N*1e6:.3f} us/call")

core.pending_tasks.clear(); core._submit_buffer.clear()
core.reference_counter._refs.clear()

t0 = time.perf_counter()
for _ in range(100_000):
    core.submit_task_from_template(tmpl, [])
dt = time.perf_counter() - t0
print(f"py submit isolated: {dt/100_000*1e6:.3f} us/call")

# remote() wrapper overhead on top of ctx.submit
core.pending_tasks.clear(); core._submit_buffer.clear()
core.reference_counter._refs.clear()
t0 = time.perf_counter()
for _ in range(100_000):
    f.remote()
dt = time.perf_counter() - t0
print(f"f.remote() isolated: {dt/100_000*1e6:.3f} us/call")
os._exit(0)
